"""Analyzer protocol: the core algebra of the engine.

An analyzer is a pair of functions ``computeStateFrom: Data -> S`` and
``computeMetricFrom: S -> M`` where ``S`` is a commutative-semigroup state
(reference `analyzers/Analyzer.scala:34-53`). On TPU a state is a pytree of
fixed-shape jax arrays; ``update`` consumes a whole column *batch* (vectorized,
never per-row) and ``merge`` is the semigroup sum used for cross-batch,
cross-device (psum-style collectives) and cross-run (incremental) merges.

Scan-sharing (reference `ScanShareableAnalyzer`, `analyzers/Analyzer.scala:
169-197`): N analyzers contribute their feature requirements; the runner
computes the union of features once per batch and calls one fused jit'd update
for all analyzers — fusion is done by XLA instead of Spark aggregate offsets.
"""

from __future__ import annotations

import abc
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Generic, List, Optional, Sequence, Tuple, TypeVar

import jax.numpy as jnp
import numpy as np

from ..data import ColumnKind, Schema
from ..expr import Predicate
from ..metrics import (
    DoubleMetric,
    Entity,
    Failure,
    Metric,
    metric_from_empty,
    metric_from_failure,
    metric_from_value,
)
from ..exceptions import (
    MetricCalculationException,
    NoColumnsSpecifiedException,
    NoSuchColumnException,
    NumberOfSpecifiedColumnsException,
    WrongColumnTypeException,
    wrap_if_necessary,
)

S = TypeVar("S")
M = TypeVar("M", bound=Metric)


# ---------------------------------------------------------------------------
# Feature specs: what a scan-shareable analyzer needs per batch on device.
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class FeatureSpec:
    """A named, device-resident numeric array derived from the batch.

    ``kind`` selects the host computation (see `runners/features.py`);
    ``payload`` carries a predicate (str or callable) or regex pattern.
    ``key`` is the stable string under which the array appears in the
    features dict handed to the fused jit'd update.
    """

    kind: str
    column: Optional[str] = None
    payload: Any = None

    @property
    def key(self) -> str:
        parts = [self.kind]
        if self.column is not None:
            parts.append(self.column)
        if self.payload is not None:
            parts.append(
                self.payload if isinstance(self.payload, str) else f"callable:{id(self.payload)}"
            )
        return ":".join(parts)


def rows_feature() -> FeatureSpec:
    return FeatureSpec("rows")


def numeric_feature(column: str) -> FeatureSpec:
    return FeatureSpec("num", column)


def mask_feature(column: str) -> FeatureSpec:
    return FeatureSpec("mask", column)


def length_feature(column: str) -> FeatureSpec:
    return FeatureSpec("len", column)


def predicate_feature(predicate: Predicate) -> FeatureSpec:
    return FeatureSpec("pred", None, predicate)


def regex_feature(column: str, pattern: str) -> FeatureSpec:
    return FeatureSpec("match", column, pattern)


def hash_feature(column: str) -> FeatureSpec:
    return FeatureSpec("hash", column)


def hll_feature(column: str) -> FeatureSpec:
    """(2, B) int32 (register index, leading-zero count) pairs for HLL++."""
    return FeatureSpec("hll", column)


def typeclass_feature(column: str) -> FeatureSpec:
    return FeatureSpec("type", column)


def codes_feature(column: str) -> FeatureSpec:
    """int32 dictionary codes of an encoded column (nulls/padding coded
    out-of-range) — the device frequency path's input."""
    return FeatureSpec("codes", column)


# ---------------------------------------------------------------------------
# Preconditions (reference `analyzers/Analyzer.scala:285-359`)
# ---------------------------------------------------------------------------


class Preconditions:
    @staticmethod
    def has_column(column: str) -> Callable[[Schema], None]:
        def check(schema: Schema) -> None:
            if column not in schema:
                raise NoSuchColumnException(f"Input data does not include column {column}!")

        return check

    @staticmethod
    def is_numeric(column: str) -> Callable[[Schema], None]:
        def check(schema: Schema) -> None:
            kind = schema[column].kind
            if not (kind.is_numeric or kind == ColumnKind.BOOLEAN):
                raise WrongColumnTypeException(
                    f"Expected type of column {column} to be numeric, but found {kind.value}!"
                )

        return check

    @staticmethod
    def is_string(column: str) -> Callable[[Schema], None]:
        def check(schema: Schema) -> None:
            if schema[column].kind != ColumnKind.STRING:
                raise WrongColumnTypeException(
                    f"Expected type of column {column} to be string, but found "
                    f"{schema[column].kind.value}!"
                )

        return check

    @staticmethod
    def is_not_nested(column: str) -> Callable[[Schema], None]:
        def check(schema: Schema) -> None:
            if schema[column].kind == ColumnKind.UNKNOWN:
                raise WrongColumnTypeException(
                    f"Unsupported nested column type of column {column}!"
                )

        return check

    @staticmethod
    def at_least_one(columns: Sequence[str]) -> Callable[[Schema], None]:
        def check(schema: Schema) -> None:
            if len(columns) == 0:
                raise NoColumnsSpecifiedException("At least one column needs to be specified!")

        return check

    @staticmethod
    def exactly_n_columns(columns: Sequence[str], n: int) -> Callable[[Schema], None]:
        def check(schema: Schema) -> None:
            if len(columns) != n:
                raise NumberOfSpecifiedColumnsException(
                    f"{n} columns have to be specified! Currently, columns contains only "
                    f"{len(columns)} column(s): {','.join(columns)}!"
                )

        return check

    @staticmethod
    def find_first_failing(
        schema: Schema, conditions: Sequence[Callable[[Schema], None]]
    ) -> Optional[MetricCalculationException]:
        for condition in conditions:
            try:
                condition(schema)
            except MetricCalculationException as exc:
                return exc
            except Exception as exc:  # noqa: BLE001
                return wrap_if_necessary(exc)
        return None


# ---------------------------------------------------------------------------
# Analyzer base classes
# ---------------------------------------------------------------------------


class Analyzer(abc.ABC, Generic[S, M]):
    """Base analyzer. Subclasses are frozen dataclasses, hashable for dedupe
    (reference dedupes analyzers against repository results,
    `AnalysisRunner.scala:116-134`)."""

    name: str = "Analyzer"

    @property
    def instance(self) -> str:
        return "*"

    @property
    def entity(self) -> Entity:
        return Entity.DATASET

    def preconditions(self) -> List[Callable[[Schema], None]]:
        return []

    @abc.abstractmethod
    def compute_metric_from(self, state: Optional[S]) -> M:
        ...

    def to_failure_metric(self, exception: BaseException) -> DoubleMetric:
        return metric_from_failure(
            wrap_if_necessary(exception), self.name, self.instance, self.entity
        )

    # semigroup ops on host-side states -------------------------------------

    def merge_states(self, a: Optional[S], b: Optional[S]) -> Optional[S]:
        """None-tolerant semigroup sum (reference `Analyzers.merge`,
        `analyzers/Analyzer.scala:361-372`)."""
        if a is None:
            return b
        if b is None:
            return a
        return self.merge(a, b)

    def merge(self, a: S, b: S) -> S:  # pragma: no cover - overridden
        raise NotImplementedError

    # slim state fetch -------------------------------------------------------

    def metric_leaves(self) -> Optional[Sequence[int]]:
        """Indices (into the flattened state pytree, ``tree_flatten`` order)
        of the leaves ``compute_metric_from`` actually reads, or ``None``
        when every leaf is metric-bearing (the safe default).

        The engine's slim fetch uses this on runs that neither persist nor
        aggregate states: only the named leaves cross the device feed link;
        the rest are reconstructed host-side from ``init_state`` identity
        values the metric never touches. An analyzer overriding this
        GUARANTEES its metric (and ``is_empty``) never read an excluded
        leaf."""
        return None


#: jit'd per-analyzer state-fold programs, keyed by (analyzer, shard count);
#: bounded LRU so a long-lived service cycling through many analyzer
#: identities / partition counts cannot grow it without limit, while hot
#: keys stay resident
from ..utils import BoundedLRU

_MERGE_FOLD_CACHE = BoundedLRU(256)


def merge_states_batched(analyzer: "Analyzer", states: Sequence[Any]) -> Optional[Any]:
    """Fold many states with the analyzer's semigroup ``merge`` in ONE
    compiled program (a lax.scan over the stacked state pytrees) instead of
    dispatching each merge's ops eagerly — on remote-tunnel devices an eager
    KLL merge alone costs ~100 dispatch round trips. States that are not
    array pytrees (e.g. frequency tables) fold sequentially on the host.
    Result order equals the left-to-right sequential fold. (A log-depth
    tree of VMAPPED pairwise merges was measured 4x SLOWER for KLL states
    on a v5e chip — the compaction cascade's dynamic_update_slices lower to
    gathers under vmap — so the sequential scan stays; see PERF.md.)"""
    states = [s for s in states if s is not None]
    if not states:
        return None
    if len(states) == 1:
        return states[0]
    import jax

    def _leaf_sig(leaf):
        # metadata only — np.asarray on an ARRAY leaf would force a blocking
        # D2H copy of every leaf of every state before the fold dispatches;
        # python-scalar leaves (no .dtype) are host values, cheap to probe
        dt = getattr(leaf, "dtype", None)
        if dt is None:
            a = np.asarray(leaf)
            return (a.shape, a.dtype)
        return (getattr(leaf, "shape", ()), np.dtype(dt))

    leaves, treedef = jax.tree_util.tree_flatten(states[0])
    array_like = bool(leaves) and all(
        hasattr(leaf, "dtype") and getattr(leaf, "dtype", None) != object
        for leaf in leaves
    )
    if array_like:
        # States persisted under different layouts (e.g. KLL sketches saved
        # before a capacity widening, or differing level counts) share a
        # treedef but not leaf shapes; np.stack would raise mid-fold. Require
        # identical leaf shapes AND dtypes, else fall back to the sequential
        # analyzer.merge fold, which handles heterogeneous states.
        sig = [_leaf_sig(leaf) for leaf in leaves]
        for s in states[1:]:
            other_leaves, other_treedef = jax.tree_util.tree_flatten(s)
            if other_treedef != treedef or [
                _leaf_sig(leaf) for leaf in other_leaves
            ] != sig:
                array_like = False
                break
    if not array_like:
        merged = states[0]
        for s in states[1:]:
            merged = analyzer.merge(merged, s)
        return merged

    stacked = jax.tree_util.tree_map(
        lambda *xs: np.stack([np.asarray(x) for x in xs]), *states
    )
    key = (analyzer, len(states))
    program = _MERGE_FOLD_CACHE.get(key)
    if program is None:
        def fold(stacked_states):
            first = jax.tree_util.tree_map(lambda x: x[0], stacked_states)
            rest = jax.tree_util.tree_map(lambda x: x[1:], stacked_states)

            def body(acc, s):
                return analyzer.merge(acc, s), None

            out, _ = jax.lax.scan(body, first, rest)
            return out

        # donate the stacked input: it is a freshly built host stack (never
        # re-read), so the fold's working buffers alias the transferred
        # copy instead of duplicating it — one fewer state-sized copy per
        # fold on the streaming plane's load->merge->persist cycle
        program = jax.jit(fold, donate_argnums=0)
        _MERGE_FOLD_CACHE[key] = program
        import warnings

        with warnings.catch_warnings():
            # first call traces+compiles: leaves whose scan carry changes
            # layout report their donated buffer as unusable — expected
            # (the donation exists for the large array leaves)
            warnings.filterwarnings(
                "ignore", message="Some donated buffers were not usable"
            )
            return jax.device_get(program(stacked))
    return jax.device_get(program(stacked))


class HostBatchContext:
    """Per-batch helper for the host ingest tier: caches predicate masks so
    N analyzers sharing a `where` filter evaluate it once (the
    `conditionalSelection` analog on the host side).

    ``run_token`` identifies the enclosing PASS (one ScanEngine run): host
    partials whose cross-batch skip caches live in the per-dataset
    ``Column.aux`` dict key their entries on it, so a second pass over the
    same dataset never reuses skip state from an earlier pass (which would
    silently drop its contribution). ``None`` disables such caches."""

    def __init__(self, batch, batch_index: int = 0, run_token=None):
        self.batch = batch
        self.batch_index = batch_index
        self.run_token = run_token
        self._pred_cache: Dict[str, np.ndarray] = {}
        self._pred_columns = None

    def pred_mask(self, predicate) -> np.ndarray:
        key = str(predicate)
        cached = self._pred_cache.get(key)
        if cached is None:
            from ..expr import evaluate_predicate
            from ..runners.features import _predicate_columns

            if self._pred_columns is None:
                self._pred_columns = _predicate_columns(self.batch)
            cached = evaluate_predicate(
                predicate, self._pred_columns, len(self.batch.row_mask)
            ) & self.batch.row_mask
            self._pred_cache[key] = cached
        return cached

    def row_mask(self, analyzer) -> np.ndarray:
        """batch row mask & the analyzer's where-filter."""
        where = getattr(analyzer, "where", None)
        if where is None:
            return self.batch.row_mask
        return self.pred_mask(where)

    def row_mask_all(self) -> bool:
        """Whether every row of the batch is valid (no padding) — gates the
        shared dictionary fast paths; cached per batch."""
        cached = self._pred_cache.get(("row_mask_all",))
        if cached is None:
            cached = bool(self.batch.row_mask.all())
            self._pred_cache[("row_mask_all",)] = cached
        return cached

    def dict_code_counts(self, column: str) -> "Optional[np.ndarray]":
        """int64[num_cats + 1] count per dictionary code over valid rows
        (masked-out/null rows in the sentinel slot) — ONE native pass per
        batch-column shared by the type-class histogram, the HLL
        present-entry fold, and the device-frequency host partial. None when
        the native kernel is unavailable (callers keep their numpy path)."""
        from ..native import native_dict_masked_bincount

        if native_dict_masked_bincount is None:
            return None
        key = ("dict_counts", column)
        cached = self._pred_cache.get(key)
        if cached is None:
            col = self.batch.column(column)
            mask = self.batch.row_mask & col.mask
            cached = native_dict_masked_bincount(
                col.codes, mask, col.num_categories
            )
            self._pred_cache[key] = cached
        return cached

    def column_mask(self, analyzer, column: str) -> np.ndarray:
        return self.row_mask(analyzer) & self.batch.column(column).mask

    def block_stats(self, analyzer, column: str) -> np.ndarray:
        """[count, sum, min, max, m2, nonnan, max_nonnan] over the
        analyzer-masked column — ONE native pass shared by
        Mean/Sum/Min/Max/StdDev (and the KLL sampler's counting half) on the
        same column (the host-tier analog of their fused device updates)."""
        where = getattr(analyzer, "where", None)
        key = ("stats", column, None if where is None else str(where))
        cached = self._pred_cache.get(key)
        if cached is None:
            col = self.batch.column(column)
            mask = self.column_mask(analyzer, column)
            vals = col.values
            if not np.issubdtype(vals.dtype, np.number):
                vals = col.numeric_f64()
            from ..native import native_block_stats

            if native_block_stats is not None:
                cached = native_block_stats(vals, mask)
            else:
                v = vals[mask].astype(np.float64)
                if v.size == 0:
                    cached = np.array([0.0, 0.0, np.nan, np.nan, 0.0, 0.0, np.nan])
                else:
                    # NaN-largest order, matching the native kernel and the
                    # device update: NaN never wins the min (no non-NaN
                    # values -> identity NaN); any NaN wins the max
                    nonnan = v[~np.isnan(v)]
                    mn = nonnan.min() if nonnan.size else np.nan
                    mx = np.nan if nonnan.size < v.size else v.max()
                    mx_nonnan = nonnan.max() if nonnan.size else np.nan
                    cached = np.array(
                        [v.size, v.sum(), mn, mx, ((v - v.mean()) ** 2).sum(),
                         float(nonnan.size), mx_nonnan]
                    )
            self._pred_cache[key] = cached
        return cached

    def peek_block_stats(self, analyzer, column: str):
        """The cached block_stats row, or None if no stats analyzer has
        computed it for this (column, where) yet — lets the KLL sampler skip
        its counting pass without forcing an extra stats pass when running
        alone."""
        where = getattr(analyzer, "where", None)
        return self._pred_cache.get(
            ("stats", column, None if where is None else str(where))
        )

    def string_lengths(self, column: str) -> np.ndarray:
        key = ("len", column)
        cached = self._pred_cache.get(key)
        if cached is None:
            from ..runners.features import (
                _is_string_dict,
                dict_string_lengths,
                string_lengths,
            )

            col = self.batch.column(column)
            if _is_string_dict(col):
                cached = dict_string_lengths(col)
            else:
                cached = string_lengths(col.string_source, col.mask)
            self._pred_cache[key] = cached
        return cached

    def type_codes(self, column: str) -> np.ndarray:
        key = ("type", column)
        cached = self._pred_cache.get(key)
        if cached is None:
            from ..runners.features import (
                _is_string_dict,
                classify_type_codes,
                dict_type_codes,
            )

            from ..data import ColumnKind

            col = self.batch.column(column)
            if _is_string_dict(col):
                cached = dict_type_codes(col)
            else:
                source = col.string_source if col.kind == ColumnKind.STRING else col.values
                cached = classify_type_codes(source, col.mask, col.kind)
            self._pred_cache[key] = cached
        return cached


class ScanShareableAnalyzer(Analyzer[S, M]):
    """Analyzer whose state updates fuse into the shared single-pass scan."""

    @abc.abstractmethod
    def feature_specs(self) -> List[FeatureSpec]:
        ...

    def scan_program_key(self) -> Tuple:
        """Extra program-identity key for the bundled device scan. Two
        analyzers sharing (class, feature-spec kinds, state shapes) AND this
        tuple run through ONE compiled update program with their feature
        arrays remapped positionally — so any instance parameter that alters
        the TRACED update logic beyond what state shapes and feature values
        express MUST appear here. Column names, where-filters, predicates,
        regexes and quantile points all act host-side (feature computation)
        or at metric time, so the default is empty."""
        return ()

    @abc.abstractmethod
    def init_state(self) -> S:
        ...

    @abc.abstractmethod
    def update(self, state: S, features: Dict[str, jnp.ndarray]) -> S:
        """Fold one batch into the state. Traced under jit; must be pure,
        fixed-shape jax ops only."""

    #: whether `host_partial` is implemented (the engine streams raw columns
    #: to the device when any requested analyzer lacks the host tier)
    supports_host_partial: bool = False

    def host_partial(self, ctx: "HostBatchContext") -> Any:
        """Per-batch partial state computed host-side by the native ingest
        tier (one C pass per block). Used when the accelerator feed link
        cannot sustain raw column streaming: the device then folds the tiny
        partials with `ingest_partial` — the same partial-aggregate-near-
        the-data + algebraic-merge split Spark executes executor-side
        (reference `AnalysisRunner.scala:303-318`, SURVEY.md §2.9)."""
        raise NotImplementedError

    def ingest_partial(self, state: S, partial: Any) -> S:
        """Fold one host partial into the device state (traced under jit).
        Default: the partial IS a state — semigroup merge."""
        return self.merge(state, partial)

    def _row_mask(self, features: Dict[str, jnp.ndarray]) -> jnp.ndarray:
        """Valid-row mask combined with this analyzer's where-filter
        (the `conditionalSelection` analog, reference
        `analyzers/Analyzer.scala:409-432`)."""
        mask = features["rows"]
        where = getattr(self, "where", None)
        if where is not None:
            mask = mask & features[predicate_feature(where).key]
        return mask


class StandardScanShareableAnalyzer(ScanShareableAnalyzer[S, DoubleMetric]):
    """Adds the success/empty/failure DoubleMetric mapping
    (reference `analyzers/Analyzer.scala:200-226`)."""

    def compute_metric_from(self, state: Optional[S]) -> DoubleMetric:
        if state is None or self.is_empty(state):
            return metric_from_empty(self.name, self.instance, self.entity)
        try:
            value = self.metric_value(state)
        except Exception as exc:  # noqa: BLE001
            return metric_from_failure(wrap_if_necessary(exc), self.name, self.instance, self.entity)
        if value is None:
            return metric_from_empty(self.name, self.instance, self.entity)
        # a NaN from a NON-empty state is a real result (Spark: max/sum/avg
        # over data containing NaN is NaN; corr with zero variance is NaN)
        # and surfaces as Success(NaN), exactly as the reference's agg row
        # does — emptiness is decided solely by `is_empty`/None
        return metric_from_value(float(value), self.name, self.instance, self.entity)

    @abc.abstractmethod
    def metric_value(self, state: S) -> float:
        ...

    def is_empty(self, state: S) -> bool:
        """Whether the folded state saw no values at all."""
        return False
