"""Scan-shareable single-pass reduction analyzers.

Each mirrors a reference analyzer (file:line cited per class) but is a
vectorized batch reduction: ``update(state, features)`` folds a whole padded
column batch into the state with pure jax ops, so XLA fuses all analyzers'
updates into one device program per pass — the TPU analog of deequ's fused
``data.agg(...)`` scan (reference `analyzers/runners/AnalysisRunner.scala:
303-318`).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import ACC_DTYPE, COUNT_DTYPE
from ..data import Schema
from ..expr import Predicate
from ..metrics import (
    Distribution,
    DistributionValue,
    DoubleMetric,
    Entity,
    HistogramMetric,
    Success,
    metric_from_empty,
)
from .base import (
    FeatureSpec,
    Preconditions,
    StandardScanShareableAnalyzer,
    ScanShareableAnalyzer,
    length_feature,
    mask_feature,
    numeric_feature,
    predicate_feature,
    regex_feature,
    rows_feature,
    typeclass_feature,
)
from .states import (
    CorrelationState,
    DataTypeHistogram,
    MaxState,
    MeanState,
    MinState,
    NumMatches,
    NumMatchesAndCount,
    StandardDeviationState,
    SumState,
    min_nan_largest,
)


def _count(mask: jnp.ndarray) -> jnp.ndarray:
    return jnp.sum(mask, dtype=COUNT_DTYPE)


def _masked_sum(values: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    return jnp.sum(jnp.where(mask, values, 0).astype(ACC_DTYPE))


def _masked_max(values: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    return jnp.max(jnp.where(mask, values, -np.inf).astype(ACC_DTYPE))


# Minimum follows Spark's NaN-largest total order (reals < +inf < NaN): a
# NaN value never wins a min, and the min over ONLY NaNs is NaN. NaN is
# therefore the top — and identity — element of this order, which is why
# MinState.init() is NaN (an empty state merges as a no-op) and why there is
# no plain masked-min helper here (it would silently reintroduce IEEE NaN
# propagation). Maximum needs no such machinery: IEEE max propagation (any
# NaN -> NaN) IS NaN-largest semantics for max, and -inf stays its identity.
# The pairwise `min_nan_largest` lives in states.py next to MinState.


def _masked_min_nl(values: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    """Batch min under the NaN-largest order: NaN values are skipped; a
    batch with no non-NaN valid value reduces to the identity NaN."""
    v = values.astype(ACC_DTYPE)
    m = mask & ~jnp.isnan(v)
    mn = jnp.min(jnp.where(m, v, np.inf))
    return jnp.where(jnp.any(m), mn, np.nan)


def _np_count(n) -> np.ndarray:
    return np.asarray(int(n), dtype=COUNT_DTYPE)


def _np_acc(x) -> np.ndarray:
    return np.asarray(x, dtype=ACC_DTYPE)


@dataclass(frozen=True)
class Size(StandardScanShareableAnalyzer[NumMatches]):
    """Row count (reference `analyzers/Size.scala:23-48`)."""

    where: Optional[Predicate] = None
    name: str = field(default="Size", init=False)

    @property
    def instance(self) -> str:
        return "*"

    @property
    def entity(self) -> Entity:
        return Entity.DATASET

    def feature_specs(self) -> List[FeatureSpec]:
        specs = [rows_feature()]
        if self.where is not None:
            specs.append(predicate_feature(self.where))
        return specs

    def init_state(self) -> NumMatches:
        return NumMatches.init()

    supports_host_partial = True

    def host_partial(self, ctx) -> NumMatches:
        return NumMatches(_np_count(np.count_nonzero(ctx.row_mask(self))))

    def update(self, state: NumMatches, features: Dict[str, jnp.ndarray]) -> NumMatches:
        return NumMatches(state.num_matches + _count(self._row_mask(features)))

    def merge(self, a: NumMatches, b: NumMatches) -> NumMatches:
        return a.merge(b)

    def metric_value(self, state: NumMatches) -> float:
        return state.metric_value()


@dataclass(frozen=True)
class _RatioAnalyzer(StandardScanShareableAnalyzer[NumMatchesAndCount]):
    """Shared logic for matches/count analyzers."""

    def init_state(self) -> NumMatchesAndCount:
        return NumMatchesAndCount.init()

    def merge(self, a: NumMatchesAndCount, b: NumMatchesAndCount) -> NumMatchesAndCount:
        return a.merge(b)

    def metric_value(self, state: NumMatchesAndCount) -> float:
        return state.metric_value()

    def is_empty(self, state: NumMatchesAndCount) -> bool:
        return int(state.count) == 0


@dataclass(frozen=True)
class Completeness(_RatioAnalyzer):
    """Fraction of non-null values (reference `analyzers/Completeness.scala:26-46`)."""

    column: str = ""
    where: Optional[Predicate] = None
    name: str = field(default="Completeness", init=False)

    @property
    def instance(self) -> str:
        return self.column

    @property
    def entity(self) -> Entity:
        return Entity.COLUMN

    def preconditions(self) -> List[Callable[[Schema], None]]:
        return [Preconditions.has_column(self.column), Preconditions.is_not_nested(self.column)]

    def feature_specs(self) -> List[FeatureSpec]:
        specs = [rows_feature(), mask_feature(self.column)]
        if self.where is not None:
            specs.append(predicate_feature(self.where))
        return specs

    supports_host_partial = True

    def host_partial(self, ctx) -> NumMatchesAndCount:
        rows = ctx.row_mask(self)
        present = ctx.batch.column(self.column).mask
        return NumMatchesAndCount(
            _np_count(np.count_nonzero(rows & present)),
            _np_count(np.count_nonzero(rows)),
        )

    def update(self, state, features):
        rows = self._row_mask(features)
        present = features[mask_feature(self.column).key]
        return NumMatchesAndCount(
            state.num_matches + _count(rows & present), state.count + _count(rows)
        )


@dataclass(frozen=True)
class Compliance(_RatioAnalyzer):
    """Fraction of rows satisfying a predicate
    (reference `analyzers/Compliance.scala:37-53`). Null predicate results
    count as non-compliant but stay in the denominator (SQL semantics)."""

    instance_name: str = ""
    predicate: Predicate = "True"
    where: Optional[Predicate] = None
    name: str = field(default="Compliance", init=False)

    @property
    def instance(self) -> str:
        return self.instance_name

    @property
    def entity(self) -> Entity:
        return Entity.COLUMN

    def feature_specs(self) -> List[FeatureSpec]:
        specs = [rows_feature(), predicate_feature(self.predicate)]
        if self.where is not None:
            specs.append(predicate_feature(self.where))
        return specs

    supports_host_partial = True

    def host_partial(self, ctx) -> NumMatchesAndCount:
        rows = ctx.row_mask(self)
        matches = ctx.pred_mask(self.predicate)
        return NumMatchesAndCount(
            _np_count(np.count_nonzero(rows & matches)),
            _np_count(np.count_nonzero(rows)),
        )

    def update(self, state, features):
        rows = self._row_mask(features)
        matches = features[predicate_feature(self.predicate).key]
        return NumMatchesAndCount(
            state.num_matches + _count(rows & matches), state.count + _count(rows)
        )


class Patterns:
    """Built-in regexes (reference `analyzers/PatternMatch.scala:58-72`)."""

    EMAIL = (
        r"""(?:[a-z0-9!#$%&'*+/=?^_`{|}~-]+(?:\.[a-z0-9!#$%&'*+/=?^_`{|}~-]+)*"""
        r"""|"(?:[\x01-\x08\x0b\x0c\x0e-\x1f\x21\x23-\x5b\x5d-\x7f]|\\[\x01-\x09\x0b\x0c\x0e-\x7f])*")"""
        r"""@(?:(?:[a-z0-9](?:[a-z0-9-]*[a-z0-9])?\.)+[a-z0-9](?:[a-z0-9-]*[a-z0-9])?"""
        r"""|\[(?:(?:25[0-5]|2[0-4][0-9]|[01]?[0-9][0-9]?)\.){3}"""
        r"""(?:25[0-5]|2[0-4][0-9]|[01]?[0-9][0-9]?|[a-z0-9-]*[a-z0-9]:"""
        r"""(?:[\x01-\x08\x0b\x0c\x0e-\x1f\x21-\x5a\x53-\x7f]|\\[\x01-\x09\x0b\x0c\x0e-\x7f])+)\])"""
    )
    URL = r"""(https?|ftp)://[^\s/$.?#].[^\s]*"""
    SOCIAL_SECURITY_NUMBER_US = (
        r"""((?!219-09-9999|078-05-1120)(?!666|000|9\d{2})\d{3}-(?!00)\d{2}-(?!0{4})\d{4})"""
        r"""|((?!219 09 9999|078 05 1120)(?!666|000|9\d{2})\d{3} (?!00)\d{2} (?!0{4})\d{4})"""
        r"""|((?!219099999|078051120)(?!666|000|9\d{2})\d{3}(?!00)\d{2}(?!0{4})\d{4})"""
    )
    CREDITCARD = (
        r"""\b(?:3[47]\d{2}([\ \-]?)\d{6}\1\d|(?:(?:4\d|5[1-5]|65)\d{2}|6011)([\ \-]?)\d{4}\2\d{4}\2)\d{4}\b"""
    )


@dataclass(frozen=True)
class PatternMatch(_RatioAnalyzer):
    """Fraction of values matching a regex, unanchored search; nulls stay in
    the denominator (reference `analyzers/PatternMatch.scala:37-55`)."""

    column: str = ""
    pattern: str = ""
    where: Optional[Predicate] = None
    name: str = field(default="PatternMatch", init=False)

    @property
    def instance(self) -> str:
        return self.column

    @property
    def entity(self) -> Entity:
        return Entity.COLUMN

    def preconditions(self) -> List[Callable[[Schema], None]]:
        return [Preconditions.has_column(self.column), Preconditions.is_string(self.column)]

    def feature_specs(self) -> List[FeatureSpec]:
        specs = [rows_feature(), regex_feature(self.column, self.pattern)]
        if self.where is not None:
            specs.append(predicate_feature(self.where))
        return specs

    supports_host_partial = True

    def host_partial(self, ctx) -> NumMatchesAndCount:
        from ..runners.features import column_regex_matches

        col = ctx.batch.column(self.column)
        rows = ctx.row_mask(self)
        matches = column_regex_matches(col, self.pattern)
        return NumMatchesAndCount(
            _np_count(np.count_nonzero(rows & matches)),
            _np_count(np.count_nonzero(rows)),
        )

    def update(self, state, features):
        rows = self._row_mask(features)
        matches = features[regex_feature(self.column, self.pattern).key]
        return NumMatchesAndCount(
            state.num_matches + _count(rows & matches), state.count + _count(rows)
        )


@dataclass(frozen=True)
class _NumericColumnAnalyzer(StandardScanShareableAnalyzer):
    """Shared preconditions/features for single numeric-column reductions."""

    column: str = ""
    where: Optional[Predicate] = None

    @property
    def instance(self) -> str:
        return self.column

    @property
    def entity(self) -> Entity:
        return Entity.COLUMN

    def preconditions(self) -> List[Callable[[Schema], None]]:
        return [Preconditions.has_column(self.column), Preconditions.is_numeric(self.column)]

    def feature_specs(self) -> List[FeatureSpec]:
        specs = [rows_feature(), numeric_feature(self.column), mask_feature(self.column)]
        if self.where is not None:
            specs.append(predicate_feature(self.where))
        return specs

    def _values_and_mask(self, features) -> Tuple[jnp.ndarray, jnp.ndarray]:
        mask = self._row_mask(features) & features[mask_feature(self.column).key]
        return features[numeric_feature(self.column).key], mask


@dataclass(frozen=True)
class Mean(_NumericColumnAnalyzer):
    """(reference `analyzers/Mean.scala:25-54`)."""

    name: str = field(default="Mean", init=False)

    def init_state(self) -> MeanState:
        return MeanState.init()

    supports_host_partial = True

    def host_partial(self, ctx) -> MeanState:
        count, total = ctx.block_stats(self, self.column)[:2]
        return MeanState(_np_acc(total), _np_count(count))

    def update(self, state, features):
        v, mask = self._values_and_mask(features)
        return MeanState(state.total + _masked_sum(v, mask), state.count + _count(mask))

    def merge(self, a, b):
        return a.merge(b)

    def metric_value(self, state) -> float:
        return state.metric_value()

    def is_empty(self, state) -> bool:
        return int(state.count) == 0


@dataclass(frozen=True)
class Sum(_NumericColumnAnalyzer):
    """(reference `analyzers/Sum.scala:25-52`)."""

    name: str = field(default="Sum", init=False)

    def init_state(self) -> SumState:
        return SumState.init()

    supports_host_partial = True

    def host_partial(self, ctx) -> SumState:
        count, total = ctx.block_stats(self, self.column)[:2]
        return SumState(_np_acc(total), _np_count(count))

    def update(self, state, features):
        v, mask = self._values_and_mask(features)
        return SumState(state.total + _masked_sum(v, mask), state.count + _count(mask))

    def merge(self, a, b):
        return a.merge(b)

    def metric_value(self, state) -> float:
        return state.metric_value()

    def is_empty(self, state) -> bool:
        return int(state.count) == 0


@dataclass(frozen=True)
class Minimum(_NumericColumnAnalyzer):
    """(reference `analyzers/Minimum.scala:25-53`)."""

    name: str = field(default="Minimum", init=False)

    def init_state(self) -> MinState:
        return MinState.init()

    supports_host_partial = True

    def host_partial(self, ctx) -> MinState:
        stats = ctx.block_stats(self, self.column)
        count, mn = stats[0], stats[2]
        # block_stats reports the NaN-largest min: NaN when the block holds
        # no non-NaN valid value — exactly MinState's identity
        return MinState(_np_acc(mn), _np_count(count))

    def update(self, state, features):
        v, mask = self._values_and_mask(features)
        return MinState(
            min_nan_largest(state.min_value, _masked_min_nl(v, mask)),
            state.count + _count(mask),
        )

    def merge(self, a, b):
        return a.merge(b)

    def metric_value(self, state) -> float:
        return state.metric_value()

    def is_empty(self, state) -> bool:
        return int(state.count) == 0


@dataclass(frozen=True)
class Maximum(_NumericColumnAnalyzer):
    """(reference `analyzers/Maximum.scala:25-53`)."""

    name: str = field(default="Maximum", init=False)

    def init_state(self) -> MaxState:
        return MaxState.init()

    supports_host_partial = True

    def host_partial(self, ctx) -> MaxState:
        stats = ctx.block_stats(self, self.column)
        count, mx = stats[0], stats[3]
        return MaxState(_np_acc(mx if count > 0 else -np.inf), _np_count(count))

    def update(self, state, features):
        v, mask = self._values_and_mask(features)
        # any valid NaN wins the max (NaN-largest order): jnp.max/jnp.maximum
        # propagate it; masked-out rows are replaced by -inf first, so a
        # null-row NaN never leaks in
        return MaxState(jnp.maximum(state.max_value, _masked_max(v, mask)), state.count + _count(mask))

    def merge(self, a, b):
        return a.merge(b)

    def metric_value(self, state) -> float:
        return state.metric_value()

    def is_empty(self, state) -> bool:
        return int(state.count) == 0


@dataclass(frozen=True)
class _LengthAnalyzer(StandardScanShareableAnalyzer):
    column: str = ""
    where: Optional[Predicate] = None

    @property
    def instance(self) -> str:
        return self.column

    @property
    def entity(self) -> Entity:
        return Entity.COLUMN

    def preconditions(self) -> List[Callable[[Schema], None]]:
        return [Preconditions.has_column(self.column), Preconditions.is_string(self.column)]

    def feature_specs(self) -> List[FeatureSpec]:
        specs = [rows_feature(), length_feature(self.column), mask_feature(self.column)]
        if self.where is not None:
            specs.append(predicate_feature(self.where))
        return specs

    def _lengths_and_mask(self, features):
        mask = self._row_mask(features) & features[mask_feature(self.column).key]
        return features[length_feature(self.column).key], mask

    def merge(self, a, b):
        return a.merge(b)

    def metric_value(self, state) -> float:
        return state.metric_value()

    def is_empty(self, state) -> bool:
        return int(state.count) == 0


@dataclass(frozen=True)
class MinLength(_LengthAnalyzer):
    """Min string length, nulls ignored (reference `analyzers/MinLength.scala:25-41`)."""

    name: str = field(default="MinLength", init=False)

    def init_state(self) -> MinState:
        return MinState.init()

    supports_host_partial = True

    def host_partial(self, ctx) -> MinState:
        lengths = ctx.string_lengths(self.column)
        mask = ctx.column_mask(self, self.column)
        n = int(np.count_nonzero(mask))
        mn = float(lengths[mask].min()) if n else np.nan  # NaN = MinState identity
        return MinState(_np_acc(mn), _np_count(n))

    def update(self, state, features):
        lengths, mask = self._lengths_and_mask(features)
        return MinState(
            min_nan_largest(state.min_value, _masked_min_nl(lengths, mask)),
            state.count + _count(mask),
        )


@dataclass(frozen=True)
class MaxLength(_LengthAnalyzer):
    """(reference `analyzers/MaxLength.scala:25-41`)."""

    name: str = field(default="MaxLength", init=False)

    def init_state(self) -> MaxState:
        return MaxState.init()

    supports_host_partial = True

    def host_partial(self, ctx) -> MaxState:
        lengths = ctx.string_lengths(self.column)
        mask = ctx.column_mask(self, self.column)
        n = int(np.count_nonzero(mask))
        mx = float(lengths[mask].max()) if n else -np.inf
        return MaxState(_np_acc(mx), _np_count(n))

    def update(self, state, features):
        lengths, mask = self._lengths_and_mask(features)
        return MaxState(
            jnp.maximum(state.max_value, _masked_max(lengths, mask)), state.count + _count(mask)
        )


@dataclass(frozen=True)
class StandardDeviation(_NumericColumnAnalyzer):
    """Population stddev via Welford/Chan merges
    (reference `analyzers/StandardDeviation.scala:25-73`)."""

    name: str = field(default="StandardDeviation", init=False)

    def init_state(self) -> StandardDeviationState:
        return StandardDeviationState.init()

    supports_host_partial = True

    def host_partial(self, ctx) -> StandardDeviationState:
        stats = ctx.block_stats(self, self.column)
        count, total, m2 = stats[0], stats[1], stats[4]
        avg = total / count if count > 0 else 0.0
        return StandardDeviationState(
            _np_acc(count), _np_acc(avg), _np_acc(m2 if count > 0 else 0.0)
        )

    def update(self, state, features):
        v, mask = self._values_and_mask(features)
        n = jnp.sum(mask, dtype=ACC_DTYPE)
        safe_n = jnp.where(n == 0, 1.0, n)
        avg = _masked_sum(v, mask) / safe_n
        centered = jnp.where(mask, v - avg, 0).astype(ACC_DTYPE)
        m2 = jnp.sum(centered * centered)
        batch = StandardDeviationState(n, jnp.where(n == 0, 0.0, avg), jnp.where(n == 0, 0.0, m2))
        return state.merge(batch)

    def merge(self, a, b):
        return a.merge(b)

    def metric_value(self, state) -> float:
        return state.metric_value()

    def is_empty(self, state) -> bool:
        return float(state.n) == 0


@dataclass(frozen=True)
class Correlation(StandardScanShareableAnalyzer[CorrelationState]):
    """Pearson correlation of two columns via mergeable co-moments
    (reference `analyzers/Correlation.scala:26-105`)."""

    first_column: str = ""
    second_column: str = ""
    where: Optional[Predicate] = None
    name: str = field(default="Correlation", init=False)

    @property
    def instance(self) -> str:
        return f"{self.first_column},{self.second_column}"

    @property
    def entity(self) -> Entity:
        return Entity.MULTICOLUMN

    def preconditions(self) -> List[Callable[[Schema], None]]:
        return [
            Preconditions.has_column(self.first_column),
            Preconditions.is_numeric(self.first_column),
            Preconditions.has_column(self.second_column),
            Preconditions.is_numeric(self.second_column),
        ]

    def feature_specs(self) -> List[FeatureSpec]:
        specs = [
            rows_feature(),
            numeric_feature(self.first_column),
            mask_feature(self.first_column),
            numeric_feature(self.second_column),
            mask_feature(self.second_column),
        ]
        if self.where is not None:
            specs.append(predicate_feature(self.where))
        return specs

    def init_state(self) -> CorrelationState:
        return CorrelationState.init()

    supports_host_partial = True

    def host_partial(self, ctx) -> CorrelationState:
        from ..native import native_block_comoments

        cx = ctx.batch.column(self.first_column)
        cy = ctx.batch.column(self.second_column)
        mask = ctx.row_mask(self) & cx.mask & cy.mask
        vx = cx.values if np.issubdtype(cx.values.dtype, np.number) else cx.numeric_f64()
        vy = cy.values if np.issubdtype(cy.values.dtype, np.number) else cy.numeric_f64()
        if native_block_comoments is not None:
            n, xs, ys, ck, xmk, ymk = native_block_comoments(vx, vy, mask)
        else:
            x, y = vx[mask].astype(np.float64), vy[mask].astype(np.float64)
            n = float(x.size)
            xs, ys = x.sum(), y.sum()
            if n > 0:
                dx, dy = x - x.mean(), y - y.mean()
                ck, xmk, ymk = (dx * dy).sum(), (dx * dx).sum(), (dy * dy).sum()
            else:
                ck = xmk = ymk = 0.0
        xa = xs / n if n > 0 else 0.0
        ya = ys / n if n > 0 else 0.0
        return CorrelationState(
            _np_acc(n), _np_acc(xa), _np_acc(ya),
            _np_acc(ck), _np_acc(xmk), _np_acc(ymk),
        )

    def update(self, state, features):
        x = features[numeric_feature(self.first_column).key]
        y = features[numeric_feature(self.second_column).key]
        mask = (
            self._row_mask(features)
            & features[mask_feature(self.first_column).key]
            & features[mask_feature(self.second_column).key]
        )
        n = jnp.sum(mask, dtype=ACC_DTYPE)
        safe_n = jnp.where(n == 0, 1.0, n)
        x_avg = _masked_sum(x, mask) / safe_n
        y_avg = _masked_sum(y, mask) / safe_n
        xc = jnp.where(mask, x - x_avg, 0).astype(ACC_DTYPE)
        yc = jnp.where(mask, y - y_avg, 0).astype(ACC_DTYPE)
        batch = CorrelationState(
            n,
            jnp.where(n == 0, 0.0, x_avg),
            jnp.where(n == 0, 0.0, y_avg),
            jnp.sum(xc * yc),
            jnp.sum(xc * xc),
            jnp.sum(yc * yc),
        )
        return state.merge(batch)

    def merge(self, a, b):
        return a.merge(b)

    def metric_value(self, state) -> float:
        return state.metric_value()

    def is_empty(self, state) -> bool:
        return float(state.n) == 0


#: order of DataTypeHistogram buckets (reference `analyzers/DataType.scala:32-52`)
DATA_TYPE_INSTANCES = ("Unknown", "Fractional", "Integral", "Boolean", "String")


@dataclass(frozen=True)
class DataType(ScanShareableAnalyzer[DataTypeHistogram, HistogramMetric]):
    """Histogram of inferred value types. Classification per value follows the
    reference decision order null -> fractional -> integral -> boolean ->
    string with the reference regexes (reference
    `analyzers/catalyst/StatefulDataType.scala:36-38`, `analyzers/DataType.scala:32-183`)."""

    column: str = ""
    where: Optional[Predicate] = None
    name: str = field(default="DataType", init=False)

    @property
    def instance(self) -> str:
        return self.column

    @property
    def entity(self) -> Entity:
        return Entity.COLUMN

    def preconditions(self) -> List[Callable[[Schema], None]]:
        return [Preconditions.has_column(self.column), Preconditions.is_not_nested(self.column)]

    def feature_specs(self) -> List[FeatureSpec]:
        specs = [rows_feature(), typeclass_feature(self.column)]
        if self.where is not None:
            specs.append(predicate_feature(self.where))
        return specs

    def init_state(self) -> DataTypeHistogram:
        return DataTypeHistogram.init()

    supports_host_partial = True

    def host_partial(self, ctx) -> DataTypeHistogram:
        from ..runners.features import TYPE_NULL, _is_string_dict, dict_entry_type_codes

        col = ctx.batch.column(self.column)
        if _is_string_dict(col) and self.where is None and ctx.row_mask_all():
            # uniform dictionary (every DISTINCT value classifies the same —
            # the overwhelmingly common shape for real string columns): the
            # histogram is just (valid count, null count), no per-code
            # bincount at all
            uniform = col.aux.get("tc_uniform")
            if uniform is None:
                tc = dict_entry_type_codes(col)
                uniform = int(tc[0]) if len(tc) and (tc == tc[0]).all() else -1
                col.aux["tc_uniform"] = uniform
            if uniform > TYPE_NULL:
                n = len(col.mask)
                n_valid = int(np.count_nonzero(col.mask))
                counts = np.zeros(5, dtype=np.int64)
                counts[uniform] = n_valid
                counts[TYPE_NULL] = n - n_valid
                return DataTypeHistogram(counts.astype(COUNT_DTYPE))
        if (
            _is_string_dict(col)
            and self.where is None
            and ctx.row_mask_all()
            and ctx.dict_code_counts(self.column) is not None
        ):
            # dictionary fast path: aggregate the shared one-pass per-code
            # counts through the cached per-DICT-ENTRY type codes — no
            # per-row type-code gather or bincount at all. The sentinel slot
            # (null values; no padding since row_mask is all-true) is
            # TYPE_NULL by the reference's semantics.
            by_code = ctx.dict_code_counts(self.column)
            tc = dict_entry_type_codes(col)
            counts = np.bincount(
                tc, weights=by_code[: col.num_categories], minlength=5
            )[:5].astype(np.int64)
            counts[TYPE_NULL] += by_code[col.num_categories]
            return DataTypeHistogram(counts.astype(COUNT_DTYPE))
        codes = ctx.type_codes(self.column)
        mask = ctx.row_mask(self)
        # all-true masks (no where-filter, unpadded host batches) skip the
        # fancy-index copy of the codes array
        masked = codes if mask.all() else codes[mask]
        counts = np.bincount(masked, minlength=5)[:5].astype(COUNT_DTYPE)
        return DataTypeHistogram(counts)

    def update(self, state, features):
        codes = features[typeclass_feature(self.column).key]
        mask = self._row_mask(features)
        # five masked sums, not a scatter-add (`.at[codes].add` lowers to a
        # serialized per-row loop on TPU); the (rows, 5) compare fuses into
        # the shared elementwise pass
        classes = jnp.arange(5, dtype=codes.dtype)
        counts = jnp.sum(
            (codes[:, None] == classes[None, :]) & mask[:, None],
            axis=0,
            dtype=COUNT_DTYPE,
        )
        return DataTypeHistogram(state.counts + counts)

    def merge(self, a, b):
        return a.merge(b)

    def compute_metric_from(self, state: Optional[DataTypeHistogram]) -> HistogramMetric:
        if state is None:
            empty = metric_from_empty(self.name, self.instance, self.entity)
            return HistogramMetric(self.entity, self.name, self.instance, empty.value, self.column)
        counts = np.asarray(state.counts)
        total = int(counts.sum())
        values = {
            DATA_TYPE_INSTANCES[i]: DistributionValue(
                int(counts[i]), (int(counts[i]) / total) if total > 0 else 0.0
            )
            for i in range(5)
        }
        dist = Distribution(values, number_of_bins=5)
        return HistogramMetric(self.entity, self.name, self.instance, Success(dist), self.column)
